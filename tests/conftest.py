"""
Test configuration: force the CPU backend (the axon TPU platform is forced
via env in this environment and rejects complex128) and expose a virtual
8-device mesh for sharding tests.
"""

import os

# Must be set before the backend initializes.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Hermetic assembly cache: the persistent matrix cache stays EXERCISED
# (its own tests depend on it; ambient solver builds hit/store too) but
# against a per-session temporary directory, so a stale ~/.cache entry
# written by a different checkout can never leak into test results.
# An explicit DEDALUS_TPU_ASSEMBLY_CACHE (e.g. the cross-process reuse
# test's subprocess env) still wins.
if "DEDALUS_TPU_ASSEMBLY_CACHE" not in os.environ:
    import atexit
    import shutil
    import tempfile

    _assembly_cache_tmp = tempfile.mkdtemp(
        prefix="dedalus_test_assembly_cache_")
    os.environ["DEDALUS_TPU_ASSEMBLY_CACHE"] = _assembly_cache_tmp
    atexit.register(shutil.rmtree, _assembly_cache_tmp, ignore_errors=True)

import pathlib  # noqa: E402
import signal  # noqa: E402
import threading  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ------------------------------------------------- service test watchdog
#
# Hard per-test timeout for the `service`, `chaos` and `ensemble`
# markers: a daemon subprocess (or an in-process daemon thread, or a
# wedged fleet reshard/collective) that hangs must not eat the tier-1
# budget silently — the SIGALRM handler kills every registered stray
# daemon, appends their captured logs to the failure message, and fails
# THIS test instead of stalling the whole sweep. Tests that spawn
# daemon subprocesses register them (with their log path) via
# `register_daemon`, imported from this conftest.

SERVICE_TEST_TIMEOUT_SEC = 180.0

_live_daemons = []   # [(Popen, log_path or None)]


def register_daemon(proc, log_path=None):
    """Track a daemon subprocess so the per-test watchdog can kill it
    and surface its log if the test hangs. Append-only: the watchdog
    snapshots a registry index when each test starts, so entries must
    not shift mid-test (pruning happens when the watchdog arms)."""
    _live_daemons.append((proc, str(log_path) if log_path else None))


def _kill_stray_daemons(since=0):
    """Kill still-running daemons registered at-or-after index `since`
    (the hanging test's own spawns); OLDER live daemons — e.g. a healthy
    module-scoped shared fixture other tests still need — are reported
    but left running. Returns log tails / notes."""
    tails = []
    for i, (proc, log_path) in enumerate(list(_live_daemons)):
        if proc.poll() is not None:
            continue
        if i < since:
            tails.append(f"pre-existing daemon pid {proc.pid} left "
                         "running (shared fixture?)")
            continue
        proc.kill()
        tails.append(f"killed stray daemon pid {proc.pid}")
        if log_path:
            try:
                text = pathlib.Path(log_path).read_text()[-2000:]
                tails.append(f"--- {log_path} (tail) ---\n{text}")
            except OSError:
                pass
    del _live_daemons[since:]
    return tails


@pytest.fixture(autouse=True)
def _service_test_watchdog(request):
    """Per-test hard watchdog for service/chaos/ensemble-marked tests
    (SIGALRM; main thread only — pytest runs tests there). On expiry:
    stray daemons are killed, their logs attached, and the test fails
    with a timeout instead of wedging tier-1. The ensemble marker rides
    the same guard because a hung fleet reshard (a collective waiting on
    a device that will never answer) stalls exactly like a hung
    daemon."""
    marked = (request.node.get_closest_marker("service") is not None
              or request.node.get_closest_marker("chaos") is not None
              or request.node.get_closest_marker("ensemble") is not None
              or request.node.get_closest_marker("batching") is not None
              or request.node.get_closest_marker("fusion") is not None
              or request.node.get_closest_marker("solvecomp") is not None
              or request.node.get_closest_marker("distributed") is not None
              or request.node.get_closest_marker("progcheck") is not None
              or request.node.get_closest_marker("threadcheck") is not None)
    if not marked or threading.current_thread() is not threading.main_thread():
        yield
        return
    timeout = SERVICE_TEST_TIMEOUT_SEC
    # drop exited entries (safe here: no test is mid-flight), then mark:
    # only daemons registered DURING this test are killed on expiry — a
    # healthy shared module fixture must survive one slow neighbor
    _live_daemons[:] = [(p, lg) for p, lg in _live_daemons
                        if p.poll() is None]
    registry_mark = len(_live_daemons)

    def on_alarm(signum, frame):
        tails = _kill_stray_daemons(since=registry_mark)
        pytest.fail(
            f"service/chaos test exceeded the {timeout:.0f}s hard "
            "watchdog (tests/conftest.py); "
            + ("; ".join(tails) if tails else "no stray daemons found"),
            pytrace=False)

    try:
        previous = signal.signal(signal.SIGALRM, on_alarm)
    except (ValueError, OSError):   # non-main thread / no SIGALRM
        yield
        return
    signal.setitimer(signal.ITIMER_REAL, timeout)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, previous)


def pytest_configure(config):
    # chaos: fault-injection tests (tools/chaos.py driving the resilient
    # loop's recovery branches). Registered here as well as in
    # pyproject.toml so the marker exists even under a bare pytest
    # invocation with a stripped ini; chaos tests are tier-1 (fast, CPU)
    # and run by default — they are the proof the recovery paths work.
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests of the resilient "
        "solve loop (tools/resilience.py + tools/chaos.py)")
    # service: warm-pool solver daemon tests (dedalus_tpu/service/ +
    # tests/test_service.py), including live-daemon subprocesses over a
    # local socket. Tier-1 by default (fast, CPU) — the serving path
    # that is not exercised does not exist.
    config.addinivalue_line(
        "markers",
        "service: warm-pool solver service tests (dedalus_tpu/service/); "
        "tier-1 by default")
    # ensemble: fleet execution tests (core/ensemble.py), including
    # device-loss resharding. Tier-1 by default; covered by the same
    # hard watchdog as service/chaos so a hung reshard cannot eat the
    # tier-1 budget.
    config.addinivalue_line(
        "markers",
        "ensemble: fleet execution tests (core/ensemble.py: vmapped/"
        "sharded stepping, device-loss resharding); tier-1 by default")
    # batching: continuous micro-batch serving tests (service/
    # batching.py), covered by the same hard watchdog — a wedged batch
    # boundary stalls exactly like a hung daemon.
    config.addinivalue_line(
        "markers",
        "batching: continuous-batching service tests (service/"
        "batching.py: micro-batch dispatch, member fault isolation); "
        "tier-1 by default")
    # fusion: fused spectral step tests (core/fusedstep.py +
    # libraries/pencilops.py fused paths). Tier-1 by default; rides the
    # same hard watchdog — a wedged fused-vs-unfused fleet comparison or
    # pallas interpret loop must not eat the tier-1 budget silently.
    config.addinivalue_line(
        "markers",
        "fusion: fused spectral step tests (core/fusedstep.py: "
        "precomposed solve/matvec/transform fusion, donation, pallas); "
        "tier-1 by default")
    # distributed: overlapped chunked transpose pipeline + 2-D
    # batch x pencil mesh composition tests. Tier-1 by default; rides
    # the same hard watchdog — a wedged collective on the virtual mesh
    # stalls exactly like a hung daemon.
    config.addinivalue_line(
        "markers",
        "distributed: overlapped distributed transpose pipeline + 2-D "
        "batch x pencil mesh tests (parallel/transposes.py, "
        "core/ensemble.py); tier-1 by default")
    # progcheck: compiled-program contract census tests (tools/lint/
    # progcheck.py). Tier-1 by default; rides the same hard watchdog —
    # a wedged census build (a hung collective on the virtual mesh)
    # stalls exactly like a hung daemon.
    config.addinivalue_line(
        "markers",
        "progcheck: compiled-program contract checker tests (tools/"
        "lint/progcheck.py: census + DTP contracts); tier-1 by default")
    # solvecomp: restructured-substitution + precision-ladder tests
    # (libraries/solvecomp.py + the pencilops/matsolvers wiring). Tier-1
    # by default; rides the same hard watchdog — a wedged banded build
    # or a hung fleet comparison stalls exactly like a hung daemon.
    config.addinivalue_line(
        "markers",
        "solvecomp: solve-composition + precision-ladder tests "
        "(libraries/solvecomp.py: associative-scan/SPIKE substitution, "
        "mixed-precision refinement); tier-1 by default")
    # threadcheck: thread-safety tier tests (tools/lint/threadcheck.py).
    # Tier-1 by default; rides the same hard watchdog — the sanitizer
    # cross-validation test drives a live in-process service worker, and
    # a wedged one stalls exactly like a hung daemon.
    config.addinivalue_line(
        "markers",
        "threadcheck: thread-safety tier tests (tools/lint/"
        "threadcheck.py: DTC rules, lock-order graph, runtime "
        "sanitizer); tier-1 by default")


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _leak_sentinel(request):
    """Opt-in tracer-leak sentinel: tests marked `leak_check` run under
    jax.checking_leaks(), so a jitted path that captures tracers in
    module/global state (the classic lifted_jit-registry hazard class)
    fails the marked test instead of surfacing as a cryptic error in some
    later trace. Opt-in because the check globally disables trace caching
    (every call retraces) — too slow for the whole suite."""
    if request.node.get_closest_marker("leak_check") is None:
        yield
        return
    with jax.checking_leaks():
        yield
