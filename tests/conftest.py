"""
Test configuration: force the CPU backend (the axon TPU platform is forced
via env in this environment and rejects complex128) and expose a virtual
8-device mesh for sharding tests.
"""

import os

# Must be set before the backend initializes.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Hermetic assembly cache: the persistent matrix cache stays EXERCISED
# (its own tests depend on it; ambient solver builds hit/store too) but
# against a per-session temporary directory, so a stale ~/.cache entry
# written by a different checkout can never leak into test results.
# An explicit DEDALUS_TPU_ASSEMBLY_CACHE (e.g. the cross-process reuse
# test's subprocess env) still wins.
if "DEDALUS_TPU_ASSEMBLY_CACHE" not in os.environ:
    import atexit
    import shutil
    import tempfile

    _assembly_cache_tmp = tempfile.mkdtemp(
        prefix="dedalus_test_assembly_cache_")
    os.environ["DEDALUS_TPU_ASSEMBLY_CACHE"] = _assembly_cache_tmp
    atexit.register(shutil.rmtree, _assembly_cache_tmp, ignore_errors=True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    # chaos: fault-injection tests (tools/chaos.py driving the resilient
    # loop's recovery branches). Registered here as well as in
    # pyproject.toml so the marker exists even under a bare pytest
    # invocation with a stripped ini; chaos tests are tier-1 (fast, CPU)
    # and run by default — they are the proof the recovery paths work.
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests of the resilient "
        "solve loop (tools/resilience.py + tools/chaos.py)")
    # service: warm-pool solver daemon tests (dedalus_tpu/service/ +
    # tests/test_service.py), including live-daemon subprocesses over a
    # local socket. Tier-1 by default (fast, CPU) — the serving path
    # that is not exercised does not exist.
    config.addinivalue_line(
        "markers",
        "service: warm-pool solver service tests (dedalus_tpu/service/); "
        "tier-1 by default")


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _leak_sentinel(request):
    """Opt-in tracer-leak sentinel: tests marked `leak_check` run under
    jax.checking_leaks(), so a jitted path that captures tracers in
    module/global state (the classic lifted_jit-registry hazard class)
    fails the marked test instead of surfacing as a cryptic error in some
    later trace. Opt-in because the check globally disables trace caching
    (every call retraces) — too slow for the whole suite."""
    if request.node.get_closest_marker("leak_check") is None:
        yield
        return
    with jax.checking_leaks():
        yield
