"""
Overlapped distributed transpose pipeline + 2-D batch x pencil mesh
composition (parallel/transposes.py, core/ensemble.py).

The contract under test: chunking a transpose+transform stage is PURE
data movement around batch-slab-invariant fft transforms, so a chunked
walk must reproduce the monolithic walk BIT-FOR-BIT while compiling to
per-chunk all_to_alls and ZERO full-state all-gathers; and a fleet on a
2-D Mesh(("batch", "pencil")) must bit-match the same fleet on a 1-D
member mesh (composition invariance — the pencil distribution of each
member's state must be invisible in the values).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import dedalus_tpu.public as d3
from dedalus_tpu.parallel import (all_to_all_transpose,
                                  DistributedPencilPipeline,
                                  distribute_solver)
from dedalus_tpu.parallel.transposes import (resolve_transpose_chunks,
                                             stage_chunks)
from dedalus_tpu.tools import retrace as retrace_mod
from dedalus_tpu.tools.config import config

pytestmark = pytest.mark.distributed

N_DEV = len(jax.devices())
needs_devices = pytest.mark.skipif(N_DEV < 4, reason="needs >= 4 devices")
needs_8 = pytest.mark.skipif(N_DEV < 8, reason="needs >= 8 devices")


class chunk_config:
    """Temporarily pin [distributed] TRANSPOSE_CHUNKS (build-scoped: the
    solver resolves it once at build)."""

    def __init__(self, value):
        self.value = str(value)

    def __enter__(self):
        self.old = config["distributed"]["TRANSPOSE_CHUNKS"]
        config["distributed"]["TRANSPOSE_CHUNKS"] = self.value

    def __exit__(self, *exc):
        config["distributed"]["TRANSPOSE_CHUNKS"] = self.old


# shared collective parser (the ad-hoc per-test regexes migrated to the
# program contract checker's size-aware machinery)
from dedalus_tpu.tools.lint.progcheck import collective_counts  # noqa: E402


def build_2d_field():
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 2 * np.pi))
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1))
    f = dist.Field(name="f", bases=(xb, zb))
    x, z = dist.local_grids(xb, zb)
    f["g"] = np.sin(3 * x) * z ** 2 + np.cos(x) * z + 1
    return f


def build_step_solver(cadence=100):
    coords = d3.CartesianCoordinates("x", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=16, bounds=(0, 4.0), dealias=3 / 2)
    zb = d3.ChebyshevT(coords["z"], size=8, bounds=(0, 1.0), dealias=3 / 2)
    u = dist.Field(name="u", bases=(xb, zb))
    t1 = dist.Field(name="t1", bases=xb)
    t2 = dist.Field(name="t2", bases=xb)
    lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
    problem = d3.IVP([u, t1, t2], namespace=locals())
    problem.add_equation("dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
    problem.add_equation("u(z=0) = 0")
    problem.add_equation("u(z=1) = 0")
    solver = problem.build_solver(d3.SBDF2, enforce_real_cadence=cadence)
    x, z = dist.local_grids(xb, zb)
    return solver, u, x, z


# --------------------------------------------------------- config + errors

def test_transpose_chunks_config_validation():
    assert resolve_transpose_chunks(1) == 1
    assert resolve_transpose_chunks("3") == 3
    assert resolve_transpose_chunks("auto") >= 2   # backend heuristic
    for bad in ("fast", "2.5", 0, -1, "0", True):
        with pytest.raises(ValueError):
            resolve_transpose_chunks(bad)
    # the config cascade path validates too (a typo'd config must fail
    # the solver build, not silently resolve)
    with chunk_config("sometimes"):
        with pytest.raises(ValueError):
            resolve_transpose_chunks()


def test_stage_chunks_clamps_to_divisors():
    assert stage_chunks(4, 8) == 4
    assert stage_chunks(4, 6) == 3
    assert stage_chunks(4, 2) == 2
    assert stage_chunks(4, 1) == 1
    assert stage_chunks(1, 64) == 1


@needs_devices
def test_all_to_all_divisibility_names_failing_axis():
    """Both moving axes are validated; the error names the bad one.
    (Before the fix only axis_out was checked — a non-divisible axis_in
    produced a wrong-shaped tiled all_to_all instead of a structured
    error.)"""
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    data = jnp.zeros((6, 8))     # axis 0 (size 6) does not divide 4
    with pytest.raises(ValueError, match=r"axis_in 0 \(size 6\)"):
        all_to_all_transpose(data, 0, 1, mesh, "x")
    data = jnp.zeros((8, 6))
    with pytest.raises(ValueError, match=r"axis_out 1 \(size 6\)"):
        all_to_all_transpose(data, 0, 1, mesh, "x")


# ----------------------------------------------- pipeline bit-identity

@needs_devices
def test_chunked_pipeline_bit_identity_2d():
    """Chunked to_grid/to_coeff round-trips are BIT-identical to the
    monolithic walk on a 2-D domain, for every chunk count the stage
    admits."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    f = build_2d_field()
    cdata = np.asarray(f["c"])
    c_sh = jax.device_put(cdata, NamedSharding(mesh, P("x", None)))
    mono = DistributedPencilPipeline(f.domain, mesh, "x", chunks=1)
    g_mono = jax.jit(mono.to_grid)(c_sh)
    c_mono = jax.jit(mono.to_coeff)(g_mono)
    assert np.allclose(np.asarray(c_mono), cdata, atol=1e-12)
    for chunks in (2, 4):
        pipe = DistributedPencilPipeline(f.domain, mesh, "x", chunks=chunks)
        g = jax.jit(pipe.to_grid)(c_sh)
        assert (np.asarray(g) == np.asarray(g_mono)).all(), chunks
        assert g.sharding.spec == P(None, "x")
        c = jax.jit(pipe.to_coeff)(g)
        assert (np.asarray(c) == np.asarray(c_mono)).all(), chunks


@needs_8
def test_chunked_pipeline_bit_identity_3d():
    """R=2 walk on a 3-D Fourier x Fourier x Chebyshev domain: both
    mesh axes' stages chunk, output still bit-matches the monolithic
    walk and the local-transform reference."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("px", "py"))
    coords = d3.CartesianCoordinates("x", "y", "z")
    dist = d3.Distributor(coords, dtype=np.float64)
    xb = d3.RealFourier(coords["x"], size=8, bounds=(0, 2 * np.pi))
    yb = d3.RealFourier(coords["y"], size=8, bounds=(0, 2 * np.pi))
    zb = d3.ChebyshevT(coords["z"], size=12, bounds=(0, 1))
    f = dist.Field(name="f", bases=(xb, yb, zb))
    x, y, z = dist.local_grids(xb, yb, zb)
    f["g"] = (np.sin(2 * x) * np.cos(y) * z ** 2 + np.cos(3 * x) * z
              + np.sin(y) + 1)
    cdata = np.asarray(f["c"])
    gdata = np.asarray(f["g"])
    c_sh = jax.device_put(cdata, NamedSharding(mesh, P("px", "py", None)))
    mono = DistributedPencilPipeline(f.domain, mesh, ("px", "py"), chunks=1)
    pipe = DistributedPencilPipeline(f.domain, mesh, ("px", "py"), chunks=2)
    g_mono = jax.jit(mono.to_grid)(c_sh)
    g = jax.jit(pipe.to_grid)(c_sh)
    assert (np.asarray(g) == np.asarray(g_mono)).all()
    assert np.allclose(np.asarray(g), gdata, atol=1e-12)
    c_back = jax.jit(pipe.to_coeff)(g)
    c_back_mono = jax.jit(mono.to_coeff)(g_mono)
    assert (np.asarray(c_back) == np.asarray(c_back_mono)).all()
    assert np.allclose(np.asarray(c_back), cdata, atol=1e-12)


# ------------------------------------------------ collective placement

@needs_devices
def test_chunked_walk_zero_gathers():
    """The zero-full-state-gather assertion (tests/test_collectives.py)
    promoted to the CHUNKED walk: the chunked pipeline compiles to one
    all_to_all per chunk and NO all-gathers."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    f = build_2d_field()
    c_sh = jax.device_put(np.asarray(f["c"]),
                          NamedSharding(mesh, P("x", None)))
    pipe = DistributedPencilPipeline(f.domain, mesh, "x", chunks=2)
    prog = jax.jit(pipe.to_grid)
    counts = collective_counts(prog.lower(c_sh).compile().as_text())
    assert counts["all-to-all"] >= 2, counts     # one per chunk
    assert counts["all-gather"] == 0, counts
    prog_c = jax.jit(pipe.to_coeff)
    g = prog(c_sh)
    counts = collective_counts(prog_c.lower(g).compile().as_text())
    assert counts["all-to-all"] >= 2, counts
    assert counts["all-gather"] == 0, counts


@needs_devices
def test_chunked_sharded_step_zero_gathers_and_bit_identity():
    """A solver BUILT with TRANSPOSE_CHUNKS=2 steps through chunked
    walk stages: its compiled advance program carries the per-chunk
    all_to_alls and zero full-state gathers, and its trajectory is
    bit-identical to the monolithic (chunks=1) sharded solver."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))

    def run(chunks, steps=5):
        with chunk_config(chunks):
            solver, u, x, z = build_step_solver()
            u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
            distribute_solver(solver, mesh)
            for _ in range(steps):
                solver.step(1e-3)
            return solver

    chunked = run(2)
    from dedalus_tpu.core.timesteppers import step_program_handle
    prog, args = step_program_handle(chunked)
    counts = collective_counts(prog.lower(*args).compile().as_text())
    assert counts["all-gather"] == 0, (
        f"full-state gathers in the chunked sharded step: {counts}")
    assert counts["all-to-all"] >= 2, counts
    mono = run(1)
    assert (np.asarray(chunked.X) == np.asarray(mono.X)).all(), (
        "chunked step trajectory diverged from monolithic")


@needs_devices
def test_zero_retraces_across_chunk_counts():
    """Chunk configs are build-time structure: two solvers built under
    different TRANSPOSE_CHUNKS each trace their programs once, and
    post-warmup stepping of either retraces nothing."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    solvers = []
    for chunks in (1, 2):
        with chunk_config(chunks):
            solver, u, x, z = build_step_solver()
            u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
            distribute_solver(solver, mesh)
            # trace + warmup: the multistep ramp burns 2 single steps,
            # then a scanned block of 4 — the same program shape the
            # post-arm window dispatches
            solver.step_many(6, 1e-3)
            solvers.append(solver)
    jax.block_until_ready([s.X for s in solvers])
    retrace_mod.sentinel.reset()
    retrace_mod.sentinel.arm()
    try:
        for solver in solvers:
            solver.step_many(4, 1e-3)
        jax.block_until_ready([s.X for s in solvers])
        assert retrace_mod.sentinel.post_arm_retraces == 0
    finally:
        retrace_mod.sentinel.reset()


@needs_8
def test_chunked_banded_distributed_matches():
    """G-chunked banded factor/solve (the 2048x1024 north-star aux
    layout: (C, Gc, ...) slabs) under a pencil mesh: the chunk dispatch
    routes through manual shard_map / unrolled chunk programs instead of
    the GSPMD chunk scan XLA's partitioner miscompiles (s64/s32
    dynamic_update_slice mismatch), and the distributed trajectory
    matches the single-device one."""
    import dedalus_tpu.public as d3_pub  # noqa: F401

    def build():
        coords = d3.CartesianCoordinates("x", "z")
        dist = d3.Distributor(coords, dtype=np.float64)
        xb = d3.RealFourier(coords["x"], size=64, bounds=(0, 4.0),
                            dealias=3 / 2)
        zb = d3.ChebyshevT(coords["z"], size=64, bounds=(0, 1.0),
                           dealias=3 / 2)
        u = dist.Field(name="u", bases=(xb, zb))
        t1 = dist.Field(name="t1", bases=xb)
        t2 = dist.Field(name="t2", bases=xb)
        lift = lambda A, n: d3.Lift(A, zb.derivative_basis(2), n)
        problem = d3.IVP([u, t1, t2], namespace=locals())
        problem.add_equation(
            "dt(u) - lap(u) + lift(t1,-1) + lift(t2,-2) = - u*u")
        problem.add_equation("u(z=0) = 0")
        problem.add_equation("u(z=1) = 0")
        solver = problem.build_solver(d3.SBDF2, matsolver="banded")
        x, z = dist.local_grids(xb, zb)
        u["g"] = np.sin(np.pi * z) * (1 + 0.3 * np.cos(np.pi * x / 2))
        return solver

    old = config["linear algebra"].get("BANDED_CHUNK_MB", "256")
    # Gc = 16 at this size: the aux comes out genuinely chunked AND the
    # chunk width tiles the 8-device mesh
    config["linear algebra"]["BANDED_CHUNK_MB"] = "0.222"
    try:
        ref = build()
        for _ in range(3):
            ref.step(1e-4)
        # the path under test is the chunked aux layout: a 4-D slab
        aux = ref.timestepper._lhs_aux
        probe = (aux["fsub"]["lastOp"] if "fsub" in aux
                 else aux["interior"][-1])
        assert probe.ndim == 4, "aux not chunked; test shape drifted"
        sh = build()
        distribute_solver(sh, Mesh(np.array(jax.devices()[:8]), ("x",)))
        for _ in range(3):
            sh.step(1e-4)
        err = np.abs(np.asarray(sh.X) - np.asarray(ref.X)).max()
        assert err < 1e-11, err
    finally:
        config["linear algebra"]["BANDED_CHUNK_MB"] = old


# ------------------------------------------------- cache/pool identity

def test_chunk_config_rekeys_solver_and_pool():
    """The resolved chunking rides the assembly-cache content key and
    the warm-pool key: pooled COMPILED programs depend on the chunk
    structure, so two chunk configs must never alias one entry."""
    from dedalus_tpu.tools import assembly_cache
    keys = {}
    for chunks in ("1", "2"):
        with chunk_config(chunks):
            solver, u, x, z = build_step_solver()
            keys[chunks] = (
                assembly_cache.solver_key(solver, solver.matrices),
                assembly_cache.pool_key(solver))
            assert solver._transpose_chunks == int(chunks)
    assert keys["1"][0] != keys["2"][0]
    assert keys["1"][1] != keys["2"][1]


# ---------------------------------------------- 2-D batch x pencil mesh

@needs_8
def test_fleet_2d_bit_matches_1d():
    """The 2-D batch x pencil composition is value-invisible: a fleet on
    Mesh((2, 4), ("batch", "pencil")) bit-matches the same fleet on a
    1-D member mesh, through multistep ramp, nonlinear stepping, AND the
    Hermitian-projection cadence (the per-variable walk/gathered-apply
    projection body)."""
    members, steps = 4, 8

    def fleet_state(mesh):
        solver, u, x, z = build_step_solver(cadence=3)
        fleet = solver.ensemble(members, mesh=mesh)

        def ics(i):
            u["g"] = np.sin(np.pi * z) * (
                1 + 0.1 * (i + 1) * np.cos(np.pi * x / 2))
        fleet.init_members(ics)
        fleet.step_many(steps, 1e-3)
        return fleet

    f1 = fleet_state(Mesh(np.array(jax.devices()[:2]), ("batch",)))
    X1 = np.asarray(f1.X)[:members]
    mesh2 = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                 ("batch", "pencil"))
    f2 = fleet_state(mesh2)
    assert f2.X.sharding.spec == P("batch", "pencil")
    X2 = np.asarray(f2.X)[:members]
    assert (X1 == X2).all(), np.abs(X1 - X2).max()
    # member IO still addresses true member rows under the 2-D sharding
    arrays1 = f1.member_arrays(1)
    arrays2 = f2.member_arrays(1)
    for k in arrays1:
        assert (arrays1[k] == arrays2[k]).all()


@needs_8
def test_fleet_2d_serving_seat_apis_bit_match():
    """Seat writes (attach/detach) and the budgeted steady dispatch
    compose with the 2-D mesh: a member seated into a running 2-D fleet
    and stepped with a budget bit-matches the 1-D fleet doing the same."""
    members = 2

    def drive(mesh):
        solver, u, x, z = build_step_solver()
        fleet = solver.ensemble(members, mesh=mesh)

        def ics(i):
            u["g"] = np.sin(np.pi * z) * (
                1 + 0.1 * (i + 1) * np.cos(np.pi * x / 2))
        fleet.init_members(ics)
        fleet.set_fleet_dt(1e-3)
        fleet.ramp_members(list(range(members)))
        fleet.step_fleet(4)
        fleet.detach_member(1)
        fleet.step_fleet(3)
        return np.asarray(fleet.X)[:members]

    X1 = drive(Mesh(np.array(jax.devices()[:2]), ("batch",)))
    X2 = drive(Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("batch", "pencil")))
    assert (X1 == X2).all(), np.abs(X1 - X2).max()


@needs_8
def test_fleet_2d_validation():
    solver, u, x, z = build_step_solver()
    devs = np.array(jax.devices()[:8])
    # pencil axis must divide the group count (G=16): 3 does not tile 8
    # devices anyway, so use a shape mismatch via names/order instead
    with pytest.raises(ValueError, match="batch"):
        solver.ensemble(4, mesh=Mesh(devs.reshape(4, 2),
                                     ("pencil", "batch")))
    with pytest.raises(ValueError, match="1-D member mesh or a 2-D"):
        solver.ensemble(4, mesh=Mesh(devs.reshape(2, 2, 2),
                                     ("batch", "pencil", "extra")))
    with pytest.raises(ValueError, match="per_member_dt"):
        solver2 = build_step_solver()[0]
        solver2.ensemble(4, mesh=Mesh(devs.reshape(2, 4),
                                      ("batch", "pencil")),
                         per_member_dt=True)


@needs_8
def test_fleet_2d_device_loss_rejected():
    """Device-loss recovery is a 1-D member-mesh feature: on a 2-D
    fleet the notification raises the documented structured error
    instead of silently mis-resharding."""
    solver, u, x, z = build_step_solver()
    mesh2 = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                 ("batch", "pencil"))
    fleet = solver.ensemble(2, mesh=mesh2)
    fleet.notify_device_loss(1)
    with pytest.raises(RuntimeError, match="1-D member meshes only"):
        fleet.step_many(1, 1e-3)
