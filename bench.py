"""
Benchmark: 2D Rayleigh-Benard IVP timesteps/sec on one chip
(progression config 3 from BASELINE.md: Fourier x Chebyshev, banded-matsolve
path, reference example: examples/ivp_2d_rayleigh_benard).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline estimate: the reference example (256x64, RK222+CFL, stop_sim_time=50)
takes ~5 cpu-minutes on a 4-core workstation (reference docstring,
examples/ivp_2d_rayleigh_benard/rayleigh_benard.py:6). With the example's
adaptive dt averaging ~0.03, that is ~1700 steps / 300 s ~= 5.7 steps/sec.
"""

import json
import sys
import time

import numpy as np
import jax

BASELINE_STEPS_PER_SEC = 5.7
NX, NZ = 256, 64
WARMUP = 10
MEASURE = 50


def main():
    backend = jax.default_backend()
    # TPU v5e: no c128, f64 emulated -> bench the f32 path on TPU, f64 on CPU.
    dtype = np.float32 if backend != "cpu" else np.float64

    sys.path.insert(0, ".")
    from __graft_entry__ import _build_rb_solver

    solver, b = _build_rb_solver(NX, NZ, dtype)
    dt = 0.01
    for _ in range(WARMUP):
        solver.step(dt)
    solver.X.block_until_ready()
    t0 = time.time()
    for _ in range(MEASURE):
        solver.step(dt)
    solver.X.block_until_ready()
    elapsed = time.time() - t0
    steps_per_sec = MEASURE / elapsed

    assert np.all(np.isfinite(np.asarray(solver.X))), "non-finite state"
    print(json.dumps({
        "metric": f"RB2D_{NX}x{NZ}_IVP_steps_per_sec_{np.dtype(dtype).name}_{backend}",
        "value": round(steps_per_sec, 3),
        "unit": "steps/sec",
        "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
