"""
Benchmark: 2D Rayleigh-Benard IVP timesteps/sec on one chip
(progression config 3 from BASELINE.md: Fourier x Chebyshev, banded-matsolve
path, reference example: examples/ivp_2d_rayleigh_benard).

Prints ONE JSON line on stdout: {"metric", "value", "unit", "vs_baseline"}.
All progress/diagnostic markers go to stderr so a timeout tail is diagnostic.

Self-defense (round-1 failure mode was a silent TPU-init crash):
  * every phase (probe, import, devices, build, warmup, measure) prints a
    timestamped marker to stderr;
  * the backend is probed in a SUBPROCESS with a timeout before this process
    commits to initializing it (a wedged PJRT plugin cannot be interrupted
    in-process);
  * TPU-init failure is retried once, then falls back to CPU so a number is
    always produced; the fallback is recorded in the metric name and an
    "error" field.

Baseline estimate: the reference example (256x64, RK222+CFL, stop_sim_time=50)
takes ~5 cpu-minutes on a 4-core workstation (reference docstring,
examples/ivp_2d_rayleigh_benard/rayleigh_benard.py:6). With the example's
adaptive dt averaging ~0.03, that is ~1700 steps / 300 s ~= 5.7 steps/sec.
"""

import json
import os
import subprocess
import sys
import time

T0 = time.time()
BASELINE_STEPS_PER_SEC = 5.7
NX, NZ = 256, 64
WARMUP = 10
MEASURE = 50
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
# Shared wedge-defense helpers (probe subprocess, plugin-strip env) live in
# __graft_entry__ so bench.py and the dryrun use identical logic.
from __graft_entry__ import (_append_result, _kill_group, _probe_devices,
                             _probe_backend_cached, _probe_backend_retrying,
                             _sanitize_jax_platforms,
                             _strip_plugin_env)  # noqa: E402


def _log_result(record):
    """Machine-record the PARENT-ACCEPTED outcome (success, fallback with
    its error context, or total failure): a figure that exists only in
    stdout/prose is a claim, not a result — and a child's own append could
    leave orphan lines for runs the parent rejects."""
    entry = {"config": f"rb{NX}x{NZ}_bench"}
    entry.update(record)
    _append_result(entry)


def mark(msg):
    print(f"[bench {time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def probe_backend(env, timeout=None):
    """Returns (ok, backend_name_or_error)."""
    backend, info = _probe_devices(env, timeout)
    return (backend is not None), (backend if backend is not None else info)


def run_benchmark():
    """The measurement itself; assumes the backend in this process works."""
    mark("importing jax")
    import numpy as np
    import jax

    backend = jax.default_backend()
    mark(f"backend={backend} devices={len(jax.devices())}")
    # TPU: no c128, f64 emulated -> bench the f32 path on TPU, f64 on CPU.
    dtype = np.float32 if backend != "cpu" else np.float64

    from __graft_entry__ import _build_rb_solver

    mark(f"building RB {NX}x{NZ} solver dtype={np.dtype(dtype).name}")
    t_build = time.time()
    solver, b = _build_rb_solver(NX, NZ, dtype)
    build_sec = time.time() - t_build
    dt = 0.01
    mark("warmup (first step compiles)")
    for i in range(WARMUP):
        solver.step(dt)
        if i == 0:
            solver.X.block_until_ready()
            mark("first step done (compile finished)")
    solver.X.block_until_ready()
    mark(f"compiling {MEASURE}-step block")
    solver.step_many(MEASURE, dt)   # one lax.scan dispatch per block
    solver.X.block_until_ready()
    mark(f"measuring {MEASURE}-step block")
    t0 = time.time()
    solver.step_many(MEASURE, dt)
    solver.X.block_until_ready()
    elapsed = time.time() - t0
    steps_per_sec = MEASURE / elapsed
    mark(f"measured {steps_per_sec:.2f} steps/s")

    assert np.all(np.isfinite(np.asarray(solver.X))), "non-finite state"
    record = {
        "metric": f"RB2D_{NX}x{NZ}_IVP_steps_per_sec_{np.dtype(dtype).name}_{backend}",
        "value": round(steps_per_sec, 3),
        "unit": "steps/sec",
        "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 3),
        # cold-start accounting: solver-construction wall time plus the
        # host_assembly/structure/factor/compile split and assembly-cache
        # verdict (tools/metrics.BuildPhases; benchmarks/coldstart.py is
        # the dedicated cold-vs-warm study)
        "build_sec": round(build_sec, 3),
        "build_phases": solver.build_phases.record(),
    }
    # Attach the sampled per-phase breakdown (tools/metrics.py; default-on,
    # cadence-gated so it never blocked inside the measured region)
    try:
        metrics_rec = solver.flush_metrics()
    except Exception as exc:
        mark(f"metrics flush failed (non-fatal): {exc}")
        metrics_rec = None
    if metrics_rec and metrics_rec.get("phase_samples"):
        record["phase_total_sec"] = metrics_rec["phase_total_sec"]
        record["phase_sum_frac"] = metrics_rec["phase_sum_frac"]
        record["phase_samples"] = metrics_rec["phase_samples"]
        if metrics_rec.get("device_mem_peak_bytes"):
            record["device_mem_peak_bytes"] = \
                metrics_rec["device_mem_peak_bytes"]
    # Numerical-health summary (tools/health.py; default-on, cadence-gated
    # like the phase sampler): checks run, warnings, ok/failed.
    try:
        health_sum = solver.health.summary()
    except Exception as exc:
        mark(f"health summary failed (non-fatal): {exc}")
        health_sum = None
    if health_sum is not None:
        record["health"] = health_sum
    # Resilience summary (tools/resilience.py): rewind/retry/resume
    # counts when the run was driven by a ResilientLoop (absent — not
    # zero — for a plain loop, so readers can tell "no resilience" from
    # "resilience, no events").
    resilience = getattr(solver, "resilience", None)
    if resilience is not None:
        try:
            record["resilience"] = resilience.summary()
        except Exception as exc:
            mark(f"resilience summary failed (non-fatal): {exc}")
    # Jit-hygiene sentinels, so the perf trajectory shows hygiene
    # regressions alongside steps/sec: post-warmup retrace count
    # (tools/retrace.py; anything nonzero means the measured loop paid
    # compile time) and static-analysis cleanliness vs the checked-in
    # baseline (tools/lint).
    try:
        from dedalus_tpu.tools.retrace import sentinel
        record["retraces_post_warmup"] = sentinel.post_arm_retraces
    except Exception as exc:
        mark(f"retrace sentinel read failed (non-fatal): {exc}")
    try:
        from dedalus_tpu.tools.lint import lint_package
        lint_summary = lint_package()
        record["lint_clean"] = (lint_summary["new"] == 0
                                and not lint_summary["stale"])
        record["lint_new_findings"] = lint_summary["new"]
    except Exception as exc:
        mark(f"lint status failed (non-fatal): {exc}")
    return record


def _run_child(env, timeout, tag):
    """Run the measurement in a fresh interpreter with a hard timeout (an
    in-process wedge — PJRT init or a hung remote compile — cannot be
    interrupted any other way). Returns (record_or_None, error_or_None)."""
    env = dict(env)
    env["_BENCH_CHILD"] = "1"
    mark(f"running benchmark in {tag} subprocess (timeout {timeout}s)")
    # own session + process-GROUP kill on timeout: a leaked chip-holding
    # grandchild is the round-2 wedge; stderr streams through live (progress
    # marks stay observable); only stdout (the JSON record) is captured
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            env=env, stdout=subprocess.PIPE, text=True,
                            start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        return None, f"{tag} child timed out after {timeout}s"
    line = next((ln for ln in out.splitlines() if ln.startswith("{")), None)
    if proc.returncode == 0 and line:
        try:
            return json.loads(line), None
        except ValueError:
            return None, f"{tag} child emitted unparsable record"
    return None, f"{tag} child rc={proc.returncode}"


def _stale_window_sec():
    """The ONE measurement window every attach/probe helper shares:
    `[bench] STALE_WINDOW_SEC` (default 48h — wide enough to span a
    round whose chip window opened early, or the previous round's sweep
    when the chip stayed unclaimable throughout). Config-backed so
    operators widen/narrow it in one place instead of chasing hardcoded
    48s through each helper."""
    try:
        from dedalus_tpu.tools.config import config
        return float(config.get("bench", "STALE_WINDOW_SEC",
                                fallback=48 * 3600.0))
    except Exception:
        return 48.0 * 3600.0


def _recent_row(predicate, max_age_sec=None):
    """Latest results.jsonl row satisfying `predicate` whose report ts
    falls inside the measurement window (default `_stale_window_sec()`;
    `max_age_sec=0` disables the window). The ONE scan loop behind the
    TPU-headline, ensemble, and serving probes, so the provenance-window
    rules can never drift between them."""
    import time
    if max_age_sec is None:
        max_age_sec = _stale_window_sec()
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "results.jsonl")
    best = None
    try:
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (predicate(row) and row.get("ts")
                        and (not max_age_sec
                             or time.time() - row["ts"] < max_age_sec)):
                    best = row
    except OSError:
        return None
    return best


def _recent_tpu_row(config=None, max_age_sec=None):
    """Latest finite backend=tpu row for `config` (default rb256x64) from
    results.jsonl recorded within the shared measurement window
    (`[bench] STALE_WINDOW_SEC` via _stale_window_sec(), as rows carry
    their own measured_ts provenance). `max_age_sec=0` disables the
    window (the stale-headline guard's unfiltered probe)."""
    config = config or f"rb{NX}x{NZ}"
    return _recent_row(
        lambda row: (row.get("config") == config
                     and row.get("backend") == "tpu"
                     and row.get("finite")
                     and row.get("steps_per_sec")),
        max_age_sec)


def _prior_headline_reuses(measured_ts, same_round_grace_hours=6.0):
    """(rounds, rerun): how many PREVIOUS official bench headline ROUNDS
    already re-reported the watcher row with this measured_ts, and whether
    the newest such report is recent enough that the current run is a
    retry of that same round (a flaky-probe re-run inside the window that
    owns the measurement, not a new reuse). Reports clustered within
    `same_round_grace_hours` count as ONE round, and refusal records
    (`stale_headline`) never count — otherwise a refusal would increment
    the tally it guards on and wedge every subsequent run."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "results.jsonl")
    report_times = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                # rows from before the stale-stamp convention carry
                # measured_ts but no `stale` flag; any headline that
                # re-reported this measurement counts as a reuse
                if (row.get("config") == f"rb{NX}x{NZ}_bench"
                        and row.get("measured_ts") == measured_ts
                        and measured_ts is not None
                        and not row.get("stale_headline")
                        and row.get("ts")):
                    report_times.append(float(row["ts"]))
    except OSError:
        pass
    if not report_times:
        return 0, False
    report_times.sort()
    grace = same_round_grace_hours * 3600.0
    rounds, anchor = 1, report_times[0]
    for t in report_times[1:]:
        if t - anchor > grace:
            rounds += 1
            anchor = t
    rerun = (time.time() - report_times[-1]) <= grace
    return rounds, rerun


def _refuse_stale(record, errors, reason):
    """Record a stale-headline refusal (loudly, rc=1): one shape for both
    refusal sites so `report` consumers see consistent fields."""
    record["stale_headline"] = reason
    record["error"] = "; ".join(errors + [f"stale_headline: {reason}"])
    mark(f"REFUSING stale headline: {reason}")
    _attach_progression(record)
    _log_result(record)
    print(json.dumps(record), flush=True)
    sys.exit(1)


def _attach_progression(record):
    """Attach this round's machine-recorded progression-config TPU rows
    (the north-star RB 2048x1024 and sphere shallow-water ell=255) so the
    official bench line carries the BASELINE.md deliverables when the
    watcher sweep landed them. These are by construction CACHED prior
    measurements, never fresh: each carries `stale: true`, its original
    `measured_ts`, and `age_s` relative to report time, so a reader can
    never mistake a re-emitted number for a new run (VERDICT rounds 4-5)."""
    for key, config in (("north_star_rb2048x1024", "rb2048x1024"),
                        ("sw_ell255", "sw_ell255")):
        row = _recent_tpu_row(config)
        if row is not None:
            record[key] = {
                "steps_per_sec": row["steps_per_sec"],
                "finite": bool(row.get("finite")),
                "build_sec": row.get("build_sec"),
                "stale": True,
                "measured_ts": row.get("ts"),
                "age_s": round(time.time() - row["ts"], 1)
                if row.get("ts") else None,
            }
    _attach_ensemble(record)
    _attach_serving(record)
    _attach_adjoint(record)
    _attach_checkpoint(record)
    _attach_fusion(record)
    _attach_solvecomp(record)
    _attach_scaling(record)
    return record


def _recent_ensemble_row(config, max_age_sec=None):
    """Latest benchmarks/ensemble.py sweep row for `config` within the
    shared measurement window. Ensemble rows are CPU-measured by design
    (the virtual member mesh; ROADMAP platform note), so unlike
    _recent_tpu_row this does not filter on backend."""
    return _recent_row(
        lambda row: (row.get("config") == config
                     and isinstance(row.get("sweep"), list)
                     and row["sweep"]
                     and row.get("speedup_n64") is not None),
        max_age_sec)


def _attach_ensemble(record):
    """Attach the newest in-window ensemble benchmark headline (fleet
    member-steps/s vs N x serial, benchmarks/ensemble.py) to the official
    bench line. Same provenance discipline as the progression rows: the
    number is a CACHED prior measurement, stamped stale with its original
    measured_ts and age so it can never pass as fresh — and the stale-
    headline guard's 48h window applies (an out-of-window row is simply
    not attached, so an ancient speedup cannot ride along forever)."""
    for key, config in (("ensemble_diffusion64", "diffusion64_ensemble"),
                        ("ensemble_rb256x64", "rb256x64_ensemble")):
        row = _recent_ensemble_row(config)
        if row is None:
            continue
        best = max(row["sweep"],
                   key=lambda p: p.get("ensemble_steps_per_sec") or 0)
        record[key] = {
            "speedup_n64": row.get("speedup_n64"),
            "meets_4x_n64": row.get("meets_4x_n64"),
            "best_members": best.get("members"),
            "best_ensemble_steps_per_sec":
                best.get("ensemble_steps_per_sec"),
            "serial_steps_per_sec":
                (row.get("serial") or {}).get("steps_per_sec"),
            "backend": row.get("backend"),
            "stale": True,
            "measured_ts": row.get("ts"),
            "age_s": round(time.time() - row["ts"], 1)
            if row.get("ts") else None,
        }
    return record


def _recent_serving_row(config, max_age_sec=None):
    """Latest benchmarks/serving.py row for `config` within the shared
    measurement window. Serving rows are CPU-measured by design (the
    daemon subprocess; ROADMAP platform note), so no backend filter."""
    return _recent_row(
        lambda row: (row.get("config") == config
                     and row.get("ttfs_speedup") is not None
                     and row.get("bit_identical_cold_warm")),
        max_age_sec)


def _attach_serving(record):
    """Attach the newest in-window serving benchmark headline (warm
    pool-hit vs cold fresh-process time-to-first-step,
    benchmarks/serving.py) to the official bench line. Same provenance
    discipline as the ensemble rows: a CACHED prior measurement, stamped
    stale with its original measured_ts and age, and dropped entirely
    once outside the 48h window."""
    for key, config in (("serving_rb256x64", "rb256x64_serving"),
                        ("serving_diffusion64", "diffusion64_serving")):
        row = _recent_serving_row(config)
        if row is None:
            continue
        record[key] = {
            "ttfs_cold_sec": row.get("ttfs_cold_sec"),
            "ttfs_warm_sec": row.get("ttfs_warm_sec"),
            "ttfs_speedup": row.get("ttfs_speedup"),
            "meets_10x": row.get("meets_10x"),
            "throughput_requests_per_sec":
                row.get("throughput_requests_per_sec"),
            "backend": row.get("backend"),
            "stale": True,
            "measured_ts": row.get("ts"),
            "age_s": round(time.time() - row["ts"], 1)
            if row.get("ts") else None,
        }
    # the continuous-batching row (benchmarks/serving.py run_batching):
    # batched vs single-executor requests/s under the same-spec
    # closed-loop storm, same stale-stamping discipline
    row = _recent_row(
        lambda r: (r.get("config") == "diffusion64_batching"
                   and r.get("requests_speedup") is not None))
    if row is not None:
        record["serving_batching"] = {
            "clients": row.get("clients"),
            "baseline_requests_per_sec":
                row.get("baseline_requests_per_sec"),
            "batched_requests_per_sec":
                row.get("batched_requests_per_sec"),
            "requests_speedup": row.get("requests_speedup"),
            "meets_1p5x": row.get("meets_1p5x"),
            "batches": row.get("batches"),
            "late_joins": row.get("late_joins"),
            "peak_batch_members": row.get("peak_batch_members"),
            "backend": row.get("backend"),
            "stale": True,
            "measured_ts": row.get("ts"),
            "age_s": round(time.time() - row["ts"], 1)
            if row.get("ts") else None,
        }
    # the overload row (benchmarks/serving.py run_overload): shed-rate +
    # bounded accepted-latency under a 2x storm, same stale-stamping
    row = _recent_row(
        lambda r: (r.get("config") == "diffusion64_overload"
                   and r.get("shed_rate") is not None))
    if row is not None:
        record["serving_overload"] = {
            "queue_depth": row.get("queue_depth"),
            "storm_rate_x": row.get("storm_rate_x"),
            "shed_rate": row.get("shed_rate"),
            "accepted_p50_sec": row.get("accepted_p50_sec"),
            "accepted_p95_sec": row.get("accepted_p95_sec"),
            "latency_bound_sec": row.get("latency_bound_sec"),
            "max_queued_observed": row.get("max_queued_observed"),
            "bounded_under_overload": row.get("bounded_under_overload"),
            "daemon_restarts": row.get("daemon_restarts"),
            "backend": row.get("backend"),
            "stale": True,
            "measured_ts": row.get("ts"),
            "age_s": round(time.time() - row["ts"], 1)
            if row.get("ts") else None,
        }
    return record


def _attach_adjoint(record):
    """Attach the newest in-window adjoint benchmark headline (grad-step
    vs forward-step cost ratio + checkpoint-segment memory sweep,
    benchmarks/adjoint.py) to the official bench line. Same provenance
    discipline as the ensemble/serving rows: a CACHED prior measurement,
    stamped stale with its original measured_ts and age, dropped once
    outside the 48h window. Adjoint rows are CPU-measured by design
    (ROADMAP platform note), so no backend filter."""
    row = _recent_row(
        lambda r: (r.get("config") == "diffusion64_adjoint"
                   and r.get("grad_forward_ratio") is not None
                   and r.get("finite")))
    if row is None:
        return record
    best_mem = min((p for p in (row.get("segments_sweep") or [])
                    if p.get("peak_rss_bytes")),
                   key=lambda p: p["peak_rss_bytes"], default=None)
    record["adjoint_diffusion64"] = {
        "grad_forward_ratio": row.get("grad_forward_ratio"),
        "grad_steps_per_sec": row.get("grad_steps_per_sec"),
        "forward_steps_per_sec": row.get("forward_steps_per_sec"),
        "fd_rel_err": row.get("fd_rel_err"),
        "n_steps": row.get("n_steps"),
        "best_mem_segments": best_mem.get("segments") if best_mem else None,
        "best_mem_peak_rss_bytes":
            best_mem.get("peak_rss_bytes") if best_mem else None,
        "backend": row.get("backend"),
        "stale": True,
        "measured_ts": row.get("ts"),
        "age_s": round(time.time() - row["ts"], 1)
        if row.get("ts") else None,
    }
    return record


def _attach_checkpoint(record):
    """Attach the newest in-window checkpointing benchmark headline
    (per-checkpoint step-loop stall by mode + restore-after-fault wall,
    benchmarks/checkpointing.py) to the official bench line. Same
    provenance discipline as the serving/adjoint rows: a CACHED prior
    measurement, stamped stale with its original measured_ts and age,
    dropped once outside the 48h window. Checkpoint rows are
    CPU-measured by design (ROADMAP platform note), so no backend
    filter."""
    row = _recent_row(
        lambda r: (r.get("config") == "rb256x64_checkpoint"
                   and r.get("stall_async_sharded_sec") is not None
                   and r.get("finite")))
    if row is None:
        return record
    record["checkpoint_rb256x64"] = {
        "stall_sync_hdf5_sec": row.get("stall_sync_hdf5_sec"),
        "stall_sync_sharded_sec": row.get("stall_sync_sharded_sec"),
        "stall_async_sharded_sec": row.get("stall_async_sharded_sec"),
        "stall_reduction_async_vs_hdf5":
            row.get("stall_reduction_async_vs_hdf5"),
        "restore_after_fault_sec": row.get("restore_after_fault_sec"),
        "checkpoints": row.get("checkpoints"),
        "backend": row.get("backend"),
        "stale": True,
        "measured_ts": row.get("ts"),
        "age_s": round(time.time() - row["ts"], 1)
        if row.get("ts") else None,
    }
    return record


def _attach_fusion(record):
    """Attach the newest in-window fusion benchmark headlines (fused vs
    unfused steps/s + per-phase breakdown, benchmarks/fusion.py) to the
    official bench line. Same provenance discipline as the ensemble/
    serving/adjoint rows: a CACHED prior measurement, stamped stale with
    its original measured_ts and age, dropped once outside the 48h
    window. Fusion rows are CPU-measured by design (ROADMAP platform
    note), so no backend filter."""
    for key, config in (("fusion_rb256x64", "rb256x64_fusion"),
                        ("fusion_diffusion64", "diffusion64_fusion")):
        row = _recent_row(
            lambda r, c=config: (r.get("config") == c
                                 and r.get("fusion_speedup") is not None
                                 and r.get("finite")))
        if row is None:
            continue
        record[key] = {
            "steps_per_sec_unfused": row.get("steps_per_sec_unfused"),
            "steps_per_sec_fused": row.get("steps_per_sec_fused"),
            "fusion_speedup": row.get("fusion_speedup"),
            "meets_1p15x": row.get("meets_1p15x"),
            "state_rel_diff": row.get("state_rel_diff"),
            "fusion": row.get("fusion"),
            "backend": row.get("backend"),
            "stale": True,
            "measured_ts": row.get("ts"),
            "age_s": round(time.time() - row["ts"], 1)
            if row.get("ts") else None,
        }
    return record


def _attach_solvecomp(record):
    """Attach the newest in-window solve-composition sweep headlines
    (sequential/ascan/spike x f64/f32+refine steps/s + accuracy,
    benchmarks/fusion.py run_solve_sweep) to the official bench line.
    Same provenance discipline as the fusion rows: a CACHED prior
    measurement, stamped stale with its original measured_ts and age,
    dropped once outside the 48h window. CPU-measured by design (ROADMAP
    platform note), so no backend filter."""
    for key, config in (("solvecomp_rb256x64", "rb256x64_solvecomp"),
                        ("solvecomp_diffusion64", "diffusion64_solvecomp")):
        row = _recent_row(
            lambda r, c=config: (r.get("config") == c
                                 and isinstance(r.get("sweep"), list)
                                 and r.get("baseline_steps_per_sec")
                                 is not None))
        if row is None:
            continue
        record[key] = {
            "baseline_steps_per_sec": row.get("baseline_steps_per_sec"),
            "best_f64_accurate": row.get("best_f64_accurate"),
            "meets_1p15x": row.get("meets_1p15x"),
            "ladder": row.get("ladder"),
            "ladder_meets_1e10": row.get("ladder_meets_1e10"),
            "sweep": [{k: c.get(k) for k in
                       ("composition", "solve_dtype", "steps_per_sec",
                        "speedup", "state_rel_err", "refine_sweeps",
                        "achieved_residual")}
                      for c in row["sweep"]],
            "backend": row.get("backend"),
            "stale": True,
            "measured_ts": row.get("ts"),
            "age_s": round(time.time() - row["ts"], 1)
            if row.get("ts") else None,
        }
    return record


def _attach_scaling(record):
    """Attach the newest in-window weak-scaling headline (steps/s per
    device count + transpose overlap split + chunked-vs-monolithic
    guard + 2048x1024 north-star shape, benchmarks/scaling.py) to the
    official bench line. Same provenance discipline as the other
    attached rows: a CACHED prior measurement, stamped stale with its
    original measured_ts and age, dropped once outside the 48h window.
    Scaling rows are measured on the virtual CPU mesh by design (ROADMAP
    platform note: the curve must survive TPU chip outages)."""
    row = _recent_row(
        lambda r: (r.get("config") == "weak_scaling"
                   and isinstance(r.get("sweep"), list)
                   and r["sweep"]
                   and isinstance(r.get("chunked_vs_mono"), dict)))
    if row is None:
        return record
    record["weak_scaling"] = {
        "sweep": [{k: p.get(k) for k in
                   ("devices", "shape", "steps_per_sec",
                    "all_to_alls", "all_gathers",
                    "transpose_exposed_sec", "transpose_overlapped_sec")}
                  for p in row["sweep"]],
        "chunks": row.get("chunks"),
        "chunked_vs_mono": row.get("chunked_vs_mono"),
        "northstar": row.get("northstar"),
        "fleet2d": row.get("fleet2d"),
        "backend": row.get("backend"),
        "stale": True,
        "measured_ts": row.get("ts"),
        "age_s": round(time.time() - row["ts"], 1)
        if row.get("ts") else None,
    }
    return record


def main():
    if os.environ.get("_BENCH_CHILD"):
        # Re-exec'd measurement child: the parent already validated this env.
        print(json.dumps(run_benchmark()), flush=True)
        return

    errors = []
    mark(f"probing backend JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '')!r}")
    # One shared env dict: the probe sanitizes JAX_PLATFORMS (and strips
    # unknown platforms it fails on) IN PLACE, so the measurement child
    # inherits the working platform list — records never carry a
    # bogus-platform init error for an entry the probe already routed
    # around.
    probe_env = _sanitize_jax_platforms(dict(os.environ))
    # several cheap probes spread over ~5 minutes: a transiently busy chip
    # should not forfeit the round (round-2 failure mode: two 240s probes
    # in one wedged window -> CPU fallback recorded as the official number).
    # TTL-cached ([bench] PROBE_CACHE_SEC): back-to-back rounds on a
    # chipless host replay the recorded verdict instead of burning the
    # ~825s exhausted retry ladder again; live probes append `kind: probe`
    # history rows so chip-return day is visible in the trajectory.
    backend, info = _probe_backend_cached(probe_env)
    ok = backend is not None
    if not ok:
        info = f"device probe failed after retries: {info}"
    else:
        info = backend
    if ok:
        mark(f"backend probe ok: {info}")
        record, err = _run_child(probe_env, 2400, "default-backend")
        if record is not None:
            _attach_progression(record)
            _log_result(record)
            print(json.dumps(record), flush=True)
            return
        mark(f"default-backend run FAILED: {err}")
        errors.append(err)
    else:
        mark(f"backend probe exhausted retries ({info}); falling back to CPU")
        errors.append(f"default-backend init failed: {info}")

    # The chip may be unclaimable at round end while the in-round watcher
    # (benchmarks/tpu_watch_bench.sh) already measured this code on TPU:
    # report that real measurement as the official number, with explicit
    # provenance, rather than a CPU number for a TPU framework.
    watcher = _recent_tpu_row()
    if watcher is None:
        # No in-window TPU measurement. If an OLDER one exists, refuse to
        # fall through silently: record the refusal loudly so the ancient
        # TPU number can never be mistaken for this round's result — and
        # the CPU fallback below never masks the staleness.
        old = _recent_tpu_row(max_age_sec=0)
        if old is not None and old.get("ts"):
            age_hours = round((time.time() - old["ts"]) / 3600.0, 2)
            record = {
                "metric": f"RB2D_{NX}x{NZ}_IVP_steps_per_sec",
                "value": 0.0, "unit": "steps/sec", "vs_baseline": 0.0,
                "measured_ts": old.get("ts"),
                "age_hours": age_hours,
            }
            _refuse_stale(record, errors,
                          f"newest TPU watcher row is {age_hours}h old "
                          f"(> 48h window); measured_ts={old['ts']}")
    if watcher is not None:
        sps = float(watcher["steps_per_sec"])
        age_s = round(time.time() - watcher["ts"], 1) \
            if watcher.get("ts") else None
        age_hours = round(age_s / 3600.0, 2) if age_s is not None else None
        reuses, same_round_rerun = _prior_headline_reuses(watcher.get("ts"))
        headline_reuse = reuses if same_round_rerun else reuses + 1
        # Re-reported cached measurement: stamped stale with its age AND
        # its measurement round so it can never pass as a fresh number —
        # the original measured_ts stays separate from the report-time
        # `ts` that _append_result stamps, and `round_measured` +
        # `headline_reuse` record how often this row has already
        # headlined an official bench line.
        record = {
            "metric": f"RB2D_{NX}x{NZ}_IVP_steps_per_sec_"
                      f"{watcher.get('dtype', 'float32')}_tpu",
            "value": round(sps, 3),
            "unit": "steps/sec",
            "vs_baseline": round(sps / BASELINE_STEPS_PER_SEC, 3),
            "backend": "tpu",
            "source": "benchmarks/results.jsonl (in-round TPU watcher "
                      "sweep; chip unclaimable at round end)",
            "stale": True,
            "measured_ts": watcher.get("ts"),
            "round_measured": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(watcher["ts"]))
            if watcher.get("ts") else None,
            "age_s": age_s,
            "age_hours": age_hours,
            "headline_reuse": headline_reuse,
            "error": "; ".join(errors),
        }
        # Stale-headline guard: a watcher row may headline ONE round when
        # the chip is unclaimable; re-reporting it in a later round would
        # let the same TPU number silently headline a third round — fail
        # loudly instead. (The >48h window is enforced upstream:
        # _recent_tpu_row only returns in-window rows, and the
        # watcher-is-None branch above refuses older ones.) A retry
        # within the grace window of the newest report is the SAME round
        # re-running (flaky probe), not a new-round reuse.
        if reuses >= 1 and not same_round_rerun:
            _refuse_stale(record, errors,
                          f"watcher row measured_ts={watcher.get('ts')} "
                          f"already headlined {reuses} prior round(s)")
        mark("chip unclaimable now; reporting the in-round watcher TPU "
             f"measurement ({sps:.1f} steps/s, {age_hours}h old, "
             f"headline reuse #{headline_reuse})")
        _attach_progression(record)
        _log_result(record)
        print(json.dumps(record), flush=True)
        return

    # CPU fallback in a fresh subprocess (this process may have a half-wedged
    # plugin registered; a clean interpreter with JAX_PLATFORMS=cpu is safer).
    env = _strip_plugin_env(os.environ)
    mark("probing CPU fallback")
    ok, info = probe_backend(env, timeout=120)
    if ok:
        record, err = _run_child(env, 1800, "cpu-fallback")
        if record is not None:
            record["error"] = "; ".join(errors)
            _attach_progression(record)
            _log_result(record)
            print(json.dumps(record), flush=True)
            return
        errors.append(err)
    else:
        errors.append(f"cpu fallback probe failed: {info}")
    failure = {
        "metric": f"RB2D_{NX}x{NZ}_IVP_steps_per_sec",
        "value": 0.0, "unit": "steps/sec", "vs_baseline": 0.0,
        "error": "; ".join(errors),
    }
    _log_result(failure)
    print(json.dumps(failure), flush=True)
    sys.exit(1)


if __name__ == "__main__":
    main()
